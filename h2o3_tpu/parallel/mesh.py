"""Device mesh resolution — the TPU-native equivalent of H2O's "cloud".

In the reference, every node gossips heartbeats until all agree on the member
list (``water/Paxos.java:27-124``) and the cloud is then locked — membership is
static for the lifetime of a job. A TPU slice has exactly that property out of
the box: the set of chips is fixed, so "cloud formation" reduces to constructing
a ``jax.sharding.Mesh`` over ``jax.devices()``.

The default mesh is 1-D over all addressable devices with axis name ``"rows"``:
frames are row-partitioned across it the way H2O chunks rows across nodes
(ESPC layout, ``water/fvec/Vec.java:152``). Multi-dim meshes (e.g. rows × model
for sharded Gram linear algebra) can be installed with :func:`set_mesh`.

Mesh resolution is TWO-LEVEL (the MXNET-MPI communicator-group shape,
PAPERS.md):

- the **process-global** mesh (:func:`global_mesh`) covers the whole device
  cloud and owns frame layout: padded lengths are computed against it so a
  frame's shape never depends on which slice later computes over it;
- a **context-bound** mesh (:func:`bind_mesh`) scopes :func:`get_mesh` to the
  current thread/task via a contextvar. A model build bound to a slice from
  :func:`slice_meshes` resolves every ``row_sharding``/``map_reduce`` against
  its OWN device subset, so two concurrent builds compile independent XLA
  programs and never share a collective rendezvous (the documented hazard
  that forced ``parallelism=1`` pins before the mesh-slice scheduler).

Slices are also the unit of ELASTIC membership (``parallel/elastic.py``,
docs/RELIABILITY.md "Elastic training"): an elastic local-SGD worker is one
slice held under a lifetime scheduler lease, so a worker that dies takes
down only its own slice's collectives — the surviving slices' programs
share no rendezvous with it and keep training.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Name of the data-parallel (row) mesh axis. Every Frame column is sharded
# along this axis; reductions over it ride ICI (lax.psum / XLA SPMD).
ROWS = "rows"

_lock = threading.Lock()
_mesh: Mesh | None = None

# Context-bound mesh: set by bind_mesh()/mesh_context(), read by get_mesh().
# A contextvar (not a global) so concurrent builds on different threads each
# see their own slice — the last-exit-clobbers race the old global-mutating
# mesh_context had cannot happen.
_bound: contextvars.ContextVar[Mesh | None] = contextvars.ContextVar(
    "h2o3_tpu_bound_mesh", default=None)

# Set (with the binding) by scheduler leases: the mesh a leased build's
# artifacts are re-homed onto at train() exit so cross-slice consumers can
# mix them — the scheduler's base mesh (the caller's mesh at scheduler
# construction; usually the global mesh). A plain mesh_context/bind_mesh
# does NOT request it (None) — its caller owns the device layout
# (device-parity tests predict INSIDE the context).
_rehome_to: contextvars.ContextVar[Mesh | None] = contextvars.ContextVar(
    "h2o3_tpu_rehome_to", default=None)


def _default_mesh() -> Mesh:
    devices = np.array(jax.devices())
    return Mesh(devices, axis_names=(ROWS,))


def _check_rows_axis(mesh: Mesh) -> None:
    if ROWS not in mesh.axis_names:
        raise ValueError(f"mesh must have a {ROWS!r} axis, got {mesh.axis_names}")


def global_mesh() -> Mesh:
    """The process-global mesh over the whole device cloud (created lazily),
    ignoring any context-bound slice. Frame layout (padding) and post-build
    re-homing resolve against this one."""
    global _mesh
    with _lock:
        if _mesh is None:
            _mesh = _default_mesh()
        return _mesh


def get_mesh() -> Mesh:
    """The mesh for the current context: the bound slice when one is active
    (see :func:`bind_mesh`), else the process-global mesh."""
    bound = _bound.get()
    if bound is not None:
        return bound
    return global_mesh()


def bound_mesh() -> Mesh | None:
    """The context-bound slice mesh, or None outside any binding."""
    return _bound.get()


def set_mesh(mesh: Mesh | None) -> None:
    """Install a mesh PROCESS-GLOBALLY (``None`` resets to the lazy default).

    The mesh must have a ``"rows"`` axis; extra axes are allowed and are used by
    model-parallel code paths (e.g. sharded Cholesky for wide GLM Gram matrices).
    For a scoped install use :func:`bind_mesh` / :func:`mesh_context` instead.
    """
    global _mesh
    if mesh is not None:
        _check_rows_axis(mesh)
    with _lock:
        _mesh = mesh


@contextlib.contextmanager
def bind_mesh(mesh: Mesh, rehome_models: bool = False,
              rehome_to: Mesh | None = None):
    """Bind ``mesh`` as this context's mesh: :func:`get_mesh` (and everything
    built on it — ``row_sharding``, ``map_reduce``, ``num_devices``) resolves
    to it inside the block, in THIS thread/task only. ``rehome_models=True``
    (scheduler leases) additionally asks builders to move finished model
    artifacts onto ``rehome_to`` (default: the global mesh) —
    :func:`rehome_requested` / :func:`rehome_target`."""
    _check_rows_axis(mesh)
    token = _bound.set(mesh)
    target = (rehome_to if rehome_to is not None else global_mesh()) \
        if rehome_models else None
    token_r = _rehome_to.set(target)
    try:
        yield mesh
    finally:
        _rehome_to.reset(token_r)
        _bound.reset(token)


def rehome_requested() -> bool:
    """True when the active binding came from a scheduler lease, i.e. the
    finished model must be re-homed onto :func:`rehome_target` for
    cross-slice consumers (predict on base-mesh frames, stacked-ensemble
    assembly)."""
    return _rehome_to.get() is not None


def rehome_target() -> Mesh | None:
    """The mesh a leased build's artifacts re-home onto (the scheduler's
    base mesh), or None outside a rehoming binding."""
    return _rehome_to.get()


def mesh_context(mesh: Mesh):
    """Temporarily use ``mesh`` as the active mesh.

    Historical API kept for callers/tests; now an alias of :func:`bind_mesh`.
    The old implementation swapped the process-global mesh and restored it on
    exit — under concurrent use the last exit clobbered everyone else's mesh
    (and a concurrent builder could resolve a foreign mesh mid-build). The
    contextvar binding is per-thread/task, so interleaved contexts are
    isolated by construction.
    """
    return bind_mesh(mesh)


def num_devices() -> int:
    """Devices along the row axis of the ACTIVE mesh (H2O:
    ``H2O.CLOUD.size()``) — the bound slice's size inside a binding."""
    mesh = get_mesh()
    return mesh.shape[ROWS]


def num_global_devices() -> int:
    """Devices along the row axis of the process-global mesh, ignoring any
    bound slice. Frame padding uses this so a frame's padded length is one
    process-wide invariant (every slice's device count divides it — see
    :func:`slice_meshes`)."""
    mesh = global_mesh()
    return mesh.shape[ROWS]


def slice_meshes(k: int, base: Mesh | None = None) -> list[Mesh]:
    """Carve ``base`` (default: the global mesh) into ``k`` disjoint
    ``rows`` submeshes.

    Each slice is a contiguous block of the base row axis with its own
    1-D ``rows`` mesh, so collectives compiled against one slice rendezvous
    only among that slice's devices — concurrent builds on different slices
    are independent XLA programs (MXNET-MPI communicator groups; FireCaffe
    independent reduction trees). ``k`` is clamped to the largest divisor of
    the base device count that is <= k, so every slice has the same size
    and the padded length stays divisible by each slice's row count — and
    elastic data shards padded to one slice's row count fit EVERY slice,
    which is what lets a dead worker's shard move to a survivor without a
    recompile (parallel/elastic.py). ``k <= 1`` (or a single-device base)
    returns ``[base]`` — the degenerate layout IS today's behavior.
    """
    g = base if base is not None else global_mesh()
    ndev = g.shape[ROWS]
    k = max(int(k), 1)
    while k > 1 and ndev % k:
        k -= 1
    if k <= 1 or ndev <= 1:
        return [g]
    if g.devices.ndim != 1:
        # multi-axis meshes are carved along rows only when rows is the sole
        # axis; otherwise degrade to the whole mesh (correct, just unsliced)
        return [g]
    per = ndev // k
    devs = np.asarray(g.devices).reshape(-1)
    return [Mesh(devs[i * per:(i + 1) * per], axis_names=(ROWS,))
            for i in range(k)]


def mesh_device_ids(mesh: Mesh) -> tuple[int, ...]:
    """Stable identity of a mesh's device set (sorted jax device ids) —
    cache keys for per-mesh resharded views and span attribution."""
    return tuple(sorted(d.id for d in np.asarray(mesh.devices).reshape(-1)))


def row_sharding(ndim: int = 1) -> NamedSharding:
    """Sharding that partitions axis 0 (rows) and replicates the rest,
    on the active (possibly bound) mesh."""
    spec = P(ROWS, *([None] * (ndim - 1)))
    return NamedSharding(get_mesh(), spec)


def replicated_sharding() -> NamedSharding:
    """Fully-replicated sharding on the active (possibly bound) mesh."""
    return NamedSharding(get_mesh(), P())


def _spec_transfers(spec, shape, mesh: Mesh):
    """``spec`` re-expressed on ``mesh`` when every partitioned axis exists
    there and still divides the array's dimension — else None (replicate).
    Preserves a slice-built array's layout across re-homing: row-sharded on
    the slice stays row-sharded on the global mesh."""
    for dim, part in enumerate(spec):
        if part is None:
            continue
        names = part if isinstance(part, tuple) else (part,)
        size = 1
        for nm in names:
            if nm not in mesh.shape:
                return None
            size *= mesh.shape[nm]
        if dim >= len(shape) or (size and shape[dim] % size):
            return None
    return spec


def rehome(obj, mesh: Mesh | None = None, _depth: int = 0,
           _seen: dict | None = None):
    """Move every jax array reachable through ``obj`` onto ``mesh`` (default:
    the global mesh), IN PLACE where possible.

    A model built inside a :func:`bind_mesh` slice holds artifacts committed
    to that slice's devices; mixing them with global-mesh frames in a later
    jit (predict, stacked-ensemble level-one assembly) raises XLA's
    incompatible-devices error. Walking the object graph once at build exit
    re-homes coefficients / tree heaps / OOF predictions. The decision comes
    from each array's EXISTING sharding, not a shape guess: an array already
    on the target device set is left exactly as the builder laid it out; a
    slice-homed array keeps its partition spec where the spec still applies
    on the target mesh (same axis names, sizes divide), else it is
    replicated. Depth- and cycle-limited like
    ``utils.memory.array_tree_bytes``; numpy arrays and scalars pass through.
    Returns the (possibly replaced) object so callers can rebind immutables.
    """
    if mesh is None:
        mesh = global_mesh()
    if _depth > 8 or obj is None or isinstance(obj, (str, bytes, int, float,
                                                     bool, type)):
        return obj
    if isinstance(obj, jax.Array):
        target = {d.id for d in np.asarray(mesh.devices).reshape(-1)}
        cur = getattr(obj, "sharding", None)
        cur_ids = {d.id for d in getattr(cur, "device_set", ())}
        if cur_ids == target:
            return obj          # already homed — keep the builder's layout
        spec = P()
        if isinstance(cur, NamedSharding):
            carried = _spec_transfers(cur.spec, obj.shape, mesh)
            if carried is not None:
                spec = carried
        return jax.device_put(obj, NamedSharding(mesh, spec))
    if isinstance(obj, np.ndarray):
        return obj
    if _seen is None:
        _seen = {}
    # memo maps id -> the RE-HOMED replacement (for in-place containers
    # that is the container itself): a second reference to an aliased
    # tuple must get the rebuilt copy, not the original whose arrays are
    # still on the slice devices
    if id(obj) in _seen:
        return _seen[id(obj)]
    _seen[id(obj)] = obj
    if isinstance(obj, dict):
        for k, v in list(obj.items()):
            obj[k] = rehome(v, mesh, _depth + 1, _seen)
        return obj
    if isinstance(obj, list):
        for i, v in enumerate(obj):
            obj[i] = rehome(v, mesh, _depth + 1, _seen)
        return obj
    if isinstance(obj, tuple):
        new = type(obj)(rehome(v, mesh, _depth + 1, _seen) for v in obj)
        _seen[id(obj)] = new
        return new
    if hasattr(obj, "__dict__"):
        for k, v in list(vars(obj).items()):
            try:
                setattr(obj, k, rehome(v, mesh, _depth + 1, _seen))
            except AttributeError:   # read-only property/slots
                pass
        return obj
    return obj
