"""Elastic local-SGD membership — workers that straggle, fault, or leave
degrade throughput instead of stalling the cloud.

Reference: H2O-3's DL trains Hogwild!-plus-model-averaging over a
peer-to-peer cloud that *survives* node trouble via UDP heartbeat gossip
(``water/H2O.java`` heartbeats, ``water/Paxos.java`` membership). Our port
trains as one SPMD program, where a single stalled participant stalls
everything — the opposite robustness profile. This module rebuilds the
reference's elasticity on TPU-native primitives (ROADMAP item 3; the
MXNET-MPI grouped-communicator embedding and the heterogeneous-worker
scheduling of PAPERS.md):

- a **worker** is a PR 9 mesh slice (``slice_meshes(k)``) leased for the
  lifetime of the group through the :class:`~h2o3_tpu.orchestration.
  scheduler.MeshScheduler` (``lease(small=True)``), running K local epochs
  per round on its own data shard;
- a **round** is the local-SGD averaging barrier: live workers' parameters
  are weighted-averaged (weights = shard weight-sums, renormalized over
  whoever reported) and the average is re-broadcast;
- a **heartbeat/progress registry** (round counters + wall-clock leases —
  the TPU-native stand-in for UDP heartbeats) drives a SUSPECT → EJECTED
  state machine: a worker that exhausts its PR 8 dispatch-retry budget
  (``ops/map_reduce.ejection_scope``), blows the per-round deadline, or
  stops heartbeating is ejected; its shards are reassigned to survivors at
  the next round boundary;
- a **(re)joining** worker catches up by cloning the latest averaged model
  before entering the next round (JOINING → ACTIVE at the boundary);
- below the ``H2O3TPU_ELASTIC_MIN_WORKERS`` quorum the build cancels with
  partial results through the PR 8 ``Job.keep_partial()`` path.

State machine (docs/RELIABILITY.md "Elastic training")::

             round reported on time
      ┌────────────────────────────────┐
      ▼                                │
   ACTIVE ──round deadline blown──▶ SUSPECT ──late result──▶ JOINING
      ▲                                │                        │
      │        lease expired ──────────┤── one grace round      │
      │                                ▼                        │
      └──── admitted at boundary ◀─ EJECTED ◀──────────────────-┘
            (clone latest average)     ▲     (rejoin() only)
     retry budget exhausted / fault ───┘

Membership is visible live: ``GET /3/Cloud`` serves a ``workers`` view
(per-worker state / round / last-heartbeat) from :data:`ELASTIC_STATS`,
and ``h2o3_elastic_rounds_total`` / ``h2o3_elastic_ejections_total{reason}``
/ ``h2o3_elastic_workers`` ride in ``/metrics``.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
import uuid
import weakref

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils import timeline as _tl
from h2o3_tpu.utils.tracing import TRACER

# -- worker states (the membership state machine) ---------------------------

JOINING, ACTIVE, SUSPECT, EJECTED = "JOINING", "ACTIVE", "SUSPECT", "EJECTED"

#: ejection causes (the ``reason`` label of h2o3_elastic_ejections_total)
R_HEARTBEAT, R_DEADLINE = "heartbeat", "deadline"
R_RETRY, R_FAULT, R_LEFT = "retry_exhausted", "fault", "left"


def min_workers_from_env(default: int = 1) -> int:
    """Quorum: live workers below this cancel the build with partial
    results (``H2O3TPU_ELASTIC_MIN_WORKERS``, default 1 — any survivor
    finishes the job)."""
    try:
        return max(int(os.environ.get("H2O3TPU_ELASTIC_MIN_WORKERS", "")
                       or default), 1)
    except ValueError:
        return default


def lease_secs_from_env(default: float = 30.0) -> float:
    """Heartbeat lease: a worker silent longer than this is considered
    dead, not slow (``H2O3TPU_ELASTIC_LEASE_SECS``)."""
    try:
        return float(os.environ.get("H2O3TPU_ELASTIC_LEASE_SECS", "")
                     or default)
    except ValueError:
        return default


def round_deadline_from_env() -> float:
    """Explicit per-round deadline in seconds
    (``H2O3TPU_ELASTIC_ROUND_DEADLINE_SECS``; 0 = adaptive — see
    :meth:`ElasticGroup._deadline_for`)."""
    try:
        return float(os.environ.get("H2O3TPU_ELASTIC_ROUND_DEADLINE_SECS",
                                    "") or 0.0)
    except ValueError:
        return 0.0


#: hard cap on any single round wait — the backstop that makes a wedged
#: first round (no duration history yet) terminate at all
ROUND_CAP_SECS = 600.0


# -- process-wide membership view (GET /3/Cloud → "workers") ----------------

class _ElasticStats:
    """Rollup behind the ``/3/Cloud`` ``workers`` membership view. Groups
    are per-build; the view must outlive them (a poller watching a finished
    build still sees its final membership). Bounded: the most recent 8
    groups are retained."""

    _MAX_GROUPS = 8

    def __init__(self):
        self._lock = lockwitness.lock("parallel.elastic._ElasticStats._lock")
        self._groups: "dict[str, list[dict]]" = {}
        self._order: list[str] = []

    def update(self, group_id: str, rows: "list[dict]") -> None:
        with self._lock:
            if group_id not in self._groups:
                self._order.append(group_id)
                while len(self._order) > self._MAX_GROUPS:
                    self._groups.pop(self._order.pop(0), None)
            self._groups[group_id] = rows

    def rows(self) -> "list[dict]":
        """Every retained worker row, newest group first."""
        with self._lock:
            out: list[dict] = []
            for gid in reversed(self._order):
                out.extend(self._groups.get(gid, ()))
            return out

    def reset(self) -> None:
        with self._lock:
            self._groups = {}
            self._order = []


#: the process-wide membership view (``GET /3/Cloud`` → ``workers``)
ELASTIC_STATS = _ElasticStats()

#: live groups, for :func:`drain` (weak — a collected group needs no drain)
_LIVE_GROUPS: "weakref.WeakSet" = weakref.WeakSet()


def live_rows() -> "list[dict]":
    """Membership rows of groups whose build is STILL RUNNING — the health
    evaluator's view (utils/health.py). :data:`ELASTIC_STATS` keeps
    finished groups for ``/3/Cloud`` pollers, but a completed build's
    workers stopped heartbeating *legitimately*: rating their silence
    against the lease would page on every finished build forever."""
    out: "list[dict]" = []
    for g in list(_LIVE_GROUPS):
        with g._cond:
            if not g.started or g._stop:
                continue
            out.extend(g._rows_locked())
    return out


def live_groups() -> "list[ElasticGroup]":
    """Groups whose build is still running — the ops-plane remediation
    seam (:mod:`h2o3_tpu.ops_plane.actions` picks the stalled worker's
    group here rather than reaching into :data:`_LIVE_GROUPS`)."""
    out: list = []
    for g in list(_LIVE_GROUPS):
        with g._cond:
            if g.started and not g._stop:
                out.append(g)
    return out


def drain(timeout: float = 30.0) -> None:
    """Join every elastic worker thread still alive.

    An EJECTED worker released from a stalled dispatch finishes that
    dispatch in the background (daemon thread, result discarded) — harmless
    in a server, but a test/bench process exiting the interpreter while XLA
    is mid-dispatch aborts. Chaos scenarios call this after releasing their
    injected stalls."""
    deadline = time.monotonic() + timeout
    for g in list(_LIVE_GROUPS):
        for w in list(g._workers.values()):
            t = w.thread
            if t is not None and t.is_alive():
                t.join(timeout=max(deadline - time.monotonic(), 0.1))


# -- the group --------------------------------------------------------------

class _Worker:
    """One membership slot: a dedicated thread holding one slice lease for
    the group's lifetime, fed rounds through a bounded-poll inbox."""

    __slots__ = ("wid", "state", "shards", "round_done", "last_heartbeat",
                 "ejected_reason", "suspect_round", "thread", "inbox",
                 "devices", "busy_seconds", "rounds_done", "strikes",
                 "exhausted_site")

    def __init__(self, wid: int):
        self.wid = wid
        self.state = ACTIVE
        self.shards: list[int] = []
        self.round_done = 0
        self.last_heartbeat = time.monotonic()
        self.ejected_reason: str | None = None
        self.suspect_round: int | None = None
        self.thread: threading.Thread | None = None
        self.inbox: queue.Queue = queue.Queue()
        self.devices: tuple = ()
        self.busy_seconds = 0.0
        self.rounds_done = 0
        # consecutive deadline misses (reset by an ON-TIME report): a
        # straggler that oscillates miss→late-post→rejoin→miss would
        # otherwise never be ejected — strike 2 ends the cycle
        self.strikes = 0
        # dispatch site an exhausted retry budget was recorded at (set by
        # the map_reduce ejection hook, consumed into the ejection record)
        self.exhausted_site: str | None = None


class ElasticGroup:
    """Membership + round barrier for elastic local-SGD training.

    The driver (``models/deeplearning.py`` ``_fit_elastic``) owns the math;
    the group owns WHO participates: it runs per-worker round thunks on
    dedicated slice-leased threads, applies the per-round deadline and
    heartbeat leases at each barrier, ejects the dead and the chronically
    slow, reassigns their shards, and admits (re)joiners. Thread-safe: every
    shared field mutates under one condition variable, and every wait on it
    is bounded (timeout + predicate recheck — the WTX001 contract)."""

    def __init__(self, n_workers: int, *, scheduler=None,
                 group_id: str | None = None, job=None,
                 lease_secs: float | None = None,
                 round_deadline_secs: float | None = None,
                 shards: "dict[int, list[int]] | None" = None):
        self.n = int(n_workers)
        self.group_id = group_id or f"elastic_{uuid.uuid4().hex[:8]}"
        self._scheduler = scheduler
        self._job = job
        self.lease_secs = (lease_secs if lease_secs is not None
                           else lease_secs_from_env())
        env_deadline = round_deadline_from_env()
        self.round_deadline_secs = (
            round_deadline_secs if round_deadline_secs is not None
            else env_deadline)
        self._cond = lockwitness.condition(
            "parallel.elastic.ElasticGroup._cond")
        self._workers = {w: _Worker(w) for w in range(self.n)}
        if shards:
            for wid, sids in shards.items():
                self._workers[wid].shards = list(sids)
        else:
            for wid in self._workers:
                self._workers[wid].shards = [wid]
        self._orphan_shards: list[int] = []
        self._reports: "dict[int, dict]" = {}
        self._round = 0
        self._stop = False
        self._join_requests: "set[int]" = set()
        self._round_ema: float | None = None
        self.rounds_completed = 0
        self.ejections: "list[dict]" = []
        self.started = False
        _LIVE_GROUPS.add(self)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ElasticGroup":
        with self._cond:
            self.started = True
        for w in self._workers.values():
            t = threading.Thread(target=self._worker_main, args=(w,),
                                 name=f"elastic-{self.group_id}-w{w.wid}",
                                 daemon=True)
            with self._cond:
                w.thread = t
            t.start()
        self._publish()
        return self

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._stop = True
        for w in self._workers.values():
            w.inbox.put(None)
        for w in self._workers.values():
            with self._cond:
                ejected = w.state == EJECTED
            t = w.thread
            if t is not None and not ejected:
                # bounded join of HEALTHY workers only: an ejected one is
                # expected-stuck inside the very dispatch it was ejected
                # for — waiting on it would re-inherit the hang this layer
                # exists to survive (its daemon thread drains in the
                # background; tests call :func:`drain` before exiting)
                t.join(timeout=timeout)
        self._publish()

    # -- worker side ---------------------------------------------------------

    def _worker_main(self, w: _Worker) -> None:
        lease_cm = (self._scheduler.lease(small=True, algo="elastic")
                    if self._scheduler is not None
                    else contextlib.nullcontext())
        with lease_cm as lease:
            if lease is not None:
                with self._cond:
                    w.devices = tuple(lease.devices)
                    w.last_heartbeat = time.monotonic()
            while True:
                try:
                    item = w.inbox.get(timeout=0.25)
                except queue.Empty:
                    with self._cond:
                        if self._stop:
                            return
                    continue
                if item is None:
                    return
                rnd, thunk = item
                self.heartbeat(w.wid)
                t0 = time.monotonic()
                err: BaseException | None = None
                out = None
                try:
                    with _tl.worker_scope(w.wid), \
                            _eject_scope(self, w.wid), \
                            TRACER.span(f"elastic_round:{rnd}",
                                        kind="elastic",
                                        attrs={"worker": w.wid,
                                               "group": self.group_id}):
                        out = thunk()
                except BaseException as e:   # noqa: BLE001 — a worker death
                    err = e                  # is a membership event, never
                                             # a group/build crash
                self._post(w, rnd, out, err, time.monotonic() - t0)

    def heartbeat(self, wid: int) -> None:
        """Progress signal — the UDP heartbeat analog. Workers call it at
        round pickup and between shard dispatches; the sweep reads staleness
        against :attr:`lease_secs`."""
        with self._cond:
            self._workers[wid].last_heartbeat = time.monotonic()

    def _post(self, w: _Worker, rnd: int, out, err, busy_s: float) -> None:
        reason = None
        with self._cond:
            w.last_heartbeat = time.monotonic()
            w.busy_seconds += busy_s
            if err is not None:
                if w.state != EJECTED:   # a swept worker can't eject twice
                    from h2o3_tpu.ops.map_reduce import DispatchFailed
                    reason = (R_RETRY if isinstance(err, DispatchFailed)
                              else R_FAULT)
                    self._eject_locked(w, reason, error=err,
                                       site=w.exhausted_site)
                w.exhausted_site = None
            elif w.state == ACTIVE and rnd == self._round:
                self._reports.setdefault(rnd, {})[w.wid] = out
                w.round_done = rnd
                w.rounds_done += 1
                w.strikes = 0          # on-time report clears the record
            elif w.state == SUSPECT:
                # straggler finished AFTER its round closed: the stale
                # result is discarded and the worker re-enters as a
                # catch-up join — it clones the latest average at the
                # next boundary instead of polluting this one
                w.state = JOINING
                w.suspect_round = None
                self._join_requests.add(w.wid)
            # EJECTED / stale posts: discarded outright
            self._cond.notify_all()
        if reason is not None:
            self._publish()

    # -- coordinator side ----------------------------------------------------

    def live_workers(self) -> "list[int]":
        with self._cond:
            return sorted(w.wid for w in self._workers.values()
                          if w.state == ACTIVE)

    def owned_shards(self, wid: int) -> "list[int]":
        with self._cond:
            return list(self._workers[wid].shards)

    def request_join(self, wid: int) -> None:
        """Ask for slot ``wid`` (an EJECTED or never-started worker) to
        re-enter at the next round boundary; it catches up by cloning the
        latest averaged model (the driver's thunks always start from the
        broadcast average, so the clone is the admission itself)."""
        with self._cond:
            w = self._workers[wid]
            if w.state in (ACTIVE, SUSPECT):
                return
            w.state = JOINING
            w.ejected_reason = None
            w.suspect_round = None
            w.strikes = 0       # an explicit (re)join starts a clean record
            self._join_requests.add(wid)
        self._publish()

    def eject(self, wid: int, reason: str = R_LEFT) -> None:
        """Explicit departure (a worker 'leaving' the cloud)."""
        with self._cond:
            w = self._workers[wid]
            if w.state != EJECTED:
                self._eject_locked(w, reason)
        self._publish()

    def preempt_reassign(self, wid: int,
                         reason: str = "ops_preempt") -> "list[int]":
        """Ops-plane preemptive reassignment: eject a silent worker NOW and
        move its shards to the least-loaded survivors immediately, instead
        of waiting for the round-boundary sweep to notice the lease expire.
        Returns the shard ids that found a new home (empty when the worker
        was already ejected or held none). The worker can re-enter later
        via :meth:`request_join` — that is the action's rollback."""
        with self._cond:
            w = self._workers.get(wid)
            if w is None or w.state == EJECTED:
                return []
            before = set(w.shards)
            self._eject_locked(w, reason)
            self._reassign_orphans_locked()
            moved = sorted(before - set(self._orphan_shards))
        self._publish()
        return moved

    def _deadline_for(self) -> float:
        if self.round_deadline_secs > 0:
            d = self.round_deadline_secs
            if self._round <= 1:
                # round 1 is also the compile round: a tight steady-state
                # deadline must not mass-suspect workers that are merely
                # waiting on XLA (fault ejection still fires immediately)
                d = max(d, 60.0)
            return min(d, ROUND_CAP_SECS)
        if self._round_ema is None:
            # no history yet (round 1 is also the compile round): only the
            # hard cap bounds it
            return ROUND_CAP_SECS
        return min(max(5.0 * self._round_ema, 2.0), ROUND_CAP_SECS)

    def run_round(self, rnd: int, thunks: "dict[int, callable]"
                  ) -> "dict[int, object]":
        """Dispatch ``thunks`` (one per live worker), wait for reports under
        the per-round deadline, then apply the membership sweep at the
        boundary: suspect the missing, eject the dead/chronically slow,
        reassign orphaned shards, admit joiners. Returns the reports that
        made it — averaging over exactly these IS the weight
        renormalization over survivors."""
        t0 = time.monotonic()
        with self._cond:
            self._round = rnd
            self._reports.setdefault(rnd, {})
        for wid, thunk in thunks.items():
            self._workers[wid].inbox.put((rnd, thunk))
        deadline = t0 + self._deadline_for()
        with self._cond:
            while True:
                missing = [wid for wid in thunks
                           if wid not in self._reports[rnd]
                           and self._workers[wid].state == ACTIVE]
                if not missing:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                # bounded wait + recheck (WTX001): a lost notify or a dead
                # worker re-polls within 250 ms, never parks forever
                self._cond.wait(timeout=min(left, 0.25))
            # -- boundary sweep (all under the one lock) --
            for wid in missing:
                self._suspect_locked(self._workers[wid])
            self._sweep_suspects_locked()
            self._reassign_orphans_locked()
            self._admit_joins_locked(rnd)
            reports = dict(self._reports.pop(rnd, {}))
            self.rounds_completed += 1
            if reports:
                # EMA over rounds that actually reported — the adaptive
                # deadline tracks real round wall, not deadline timeouts
                wall = time.monotonic() - t0
                self._round_ema = (wall if self._round_ema is None
                                   else 0.5 * self._round_ema + 0.5 * wall)
        _tm.ELASTIC_ROUNDS.inc()
        self._publish()
        return reports

    # -- state machine (all *_locked run under self._cond) -------------------

    def _suspect_locked(self, w: _Worker) -> None:
        if w.state != ACTIVE:
            return
        w.strikes += 1
        if w.strikes >= 2:
            # second consecutive deadline miss: a straggler that posts late
            # and rejoins between misses (ACTIVE→SUSPECT→JOINING→ACTIVE)
            # would oscillate forever — the strike counter survives the
            # catch-up join and ends the cycle (docs: blows the per-round
            # deadline twice ⇒ ejected)
            self._eject_locked(w, R_DEADLINE)
            return
        w.state = SUSPECT
        w.suspect_round = self._round

    def _sweep_suspects_locked(self) -> None:
        now = time.monotonic()
        for w in self._workers.values():
            if w.state != SUSPECT:
                continue
            if now - w.last_heartbeat > self.lease_secs:
                # silent past its lease: dead, not slow
                self._eject_locked(w, R_HEARTBEAT)
            elif self._round - (w.suspect_round or self._round) >= 1:
                # still heartbeating but missed a second boundary: a
                # chronic straggler holds the whole cloud's averaging
                # cadence hostage — eject it (it can rejoin and catch up)
                self._eject_locked(w, R_DEADLINE)

    def _eject_locked(self, w: _Worker, reason: str, error=None,
                      site: str | None = None) -> None:
        w.state = EJECTED
        w.ejected_reason = reason
        w.suspect_round = None
        # graftlint: ok(_locked suffix: every caller holds self._cond)
        self._orphan_shards.extend(w.shards)
        w.shards = []
        rec = {"worker": w.wid, "reason": reason, "round": self._round,
               "at_monotonic": time.monotonic()}
        if error is not None:
            rec["error"] = f"{type(error).__name__}: {error}"
        if site is not None:
            # which dispatch site burned the retry budget — recorded by the
            # map_reduce ejection hook at the site itself, where the name
            # is still known even if the exception gets wrapped on the way
            rec["site"] = site
        # graftlint: ok(_locked suffix: every caller holds self._cond)
        self.ejections.append(rec)
        _tm.ELASTIC_EJECTIONS.labels(reason=reason).inc()
        _tl.TIMELINE.record("elastic", f"eject:w{w.wid}:{reason}")
        if self._job is not None:
            # served by JobV3 as workers_ejected so pollers watch
            # membership decay live
            with self._job._lock:
                self._job.workers_ejected = \
                    getattr(self._job, "workers_ejected", 0) + 1

    def _reassign_orphans_locked(self) -> None:
        """An ejected worker's data shards move to the least-loaded
        survivors at the round boundary (lowest shard count, ties to the
        lowest id — deterministic), so coverage of the training data
        survives membership decay."""
        if not self._orphan_shards:
            return
        live = sorted((w for w in self._workers.values()
                       if w.state == ACTIVE),
                      key=lambda w: (len(w.shards), w.wid))
        if not live:
            return      # nobody to take them — retry at the next boundary
        for sid in sorted(self._orphan_shards):
            tgt = min(live, key=lambda w: (len(w.shards), w.wid))
            tgt.shards.append(sid)
        # graftlint: ok(_locked suffix: every caller holds self._cond)
        self._orphan_shards = []

    def _admit_joins_locked(self, rnd: int) -> None:
        for wid in sorted(self._join_requests):
            w = self._workers[wid]
            if w.state != JOINING:
                continue
            w.state = ACTIVE
            w.round_done = rnd
            w.last_heartbeat = time.monotonic()
            # rebalance: orphans first, else steal one shard from the
            # most-loaded peer (never its last one)
            if not w.shards:
                donor = max((p for p in self._workers.values()
                             if p.state == ACTIVE and len(p.shards) > 1),
                            key=lambda p: (len(p.shards), -p.wid),
                            default=None)
                if donor is not None:
                    w.shards.append(donor.shards.pop())
        # graftlint: ok(_locked suffix: every caller holds self._cond)
        self._join_requests.clear()

    # -- views ---------------------------------------------------------------

    @property
    def ejected_total(self) -> int:
        with self._cond:
            return len(self.ejections)

    def membership(self) -> "dict[int, str]":
        with self._cond:
            return {w.wid: w.state for w in self._workers.values()}

    def summary(self) -> dict:
        """Build-level rollup for model output / bench ``extra.elastic``."""
        with self._cond:
            by_reason: dict = {}
            for e in self.ejections:
                by_reason[e["reason"]] = by_reason.get(e["reason"], 0) + 1
            return {
                "group": self.group_id,
                "workers": self.n,
                "live": sum(1 for w in self._workers.values()
                            if w.state == ACTIVE),
                "rounds": self.rounds_completed,
                "ejections": [dict(e) for e in self.ejections],
                "ejections_by_reason": by_reason,
                "per_worker": {
                    w.wid: {"state": w.state,
                            "rounds_done": w.rounds_done,
                            "busy_seconds": round(w.busy_seconds, 4),
                            "shards": list(w.shards)}
                    for w in self._workers.values()},
            }

    def rows(self) -> "list[dict]":
        """Membership rows (public — the ops-plane remediation reads gaps
        here without reaching into the condition lock)."""
        with self._cond:
            return self._rows_locked()

    def _rows_locked(self) -> "list[dict]":
        now = time.monotonic()
        return [{"worker": w.wid, "group": self.group_id, "state": w.state,
                 "round": w.round_done,
                 "last_heartbeat_ago_ms":
                     round((now - w.last_heartbeat) * 1e3, 1),
                 "devices": list(w.devices), "shards": list(w.shards),
                 "ejected_reason": w.ejected_reason}
                for w in self._workers.values()]

    def _publish(self) -> None:
        with self._cond:
            rows = self._rows_locked()
            live = sum(1 for w in self._workers.values()
                       if w.state == ACTIVE)
        ELASTIC_STATS.update(self.group_id, rows)
        _tm.ELASTIC_WORKERS.set(live)


@contextlib.contextmanager
def _eject_scope(group: ElasticGroup, wid: int):
    """Bind the map_reduce ejection hook for one worker's round: an
    exhausted dispatch-retry budget deep inside any dispatch site records
    the SITE NAME as this worker's pending ejection cause — the
    DispatchFailed that follows unwinds only the worker's round, and
    :meth:`ElasticGroup._post` folds the site into the ejection record
    (the name is known here, at the site, even if the exception gets
    wrapped on the way out)."""
    from h2o3_tpu.ops.map_reduce import ejection_scope

    def hook(what: str, history: list) -> None:
        with group._cond:
            group._workers[wid].exhausted_site = what
        _tl.TIMELINE.record("elastic",
                            f"retry_exhausted:w{wid}:{what}")

    with ejection_scope(hook):
        yield
