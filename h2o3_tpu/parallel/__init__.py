"""Parallel substrate: device mesh management and collective helpers.

Replaces the reference's distributed runtime (cloud formation ``water/Paxos.java``,
RPC ``water/RPC.java``, transport ``water/TCPReceiverThread.java``): on TPU the
"cloud" is the JAX device mesh — membership is static per slice, transport is ICI
driven by XLA collectives, and there is no user-level RPC to implement.
"""

from h2o3_tpu.parallel.mesh import (
    ROWS,
    bind_mesh,
    bound_mesh,
    get_mesh,
    global_mesh,
    set_mesh,
    mesh_context,
    mesh_device_ids,
    num_devices,
    num_global_devices,
    rehome,
    row_sharding,
    replicated_sharding,
    slice_meshes,
)

__all__ = [
    "ROWS",
    "bind_mesh",
    "bound_mesh",
    "get_mesh",
    "global_mesh",
    "set_mesh",
    "mesh_context",
    "mesh_device_ids",
    "num_devices",
    "num_global_devices",
    "rehome",
    "row_sharding",
    "replicated_sharding",
    "slice_meshes",
]
