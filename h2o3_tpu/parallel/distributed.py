"""Multi-process (multi-host) runtime — the TPU-native "cloud".

Reference: a multi-node H2O cloud forms by heartbeat gossip until every JVM
agrees on the member list (``water/H2O.java:1890`` ``startLocalNode``,
``:2099`` ``waitForCloudSize``; ``water/Paxos.java``). The TPU equivalent is
JAX's multi-controller runtime: every process runs the same program, calls
:func:`jax.distributed.initialize` against a coordinator address, and the
global device mesh — spanning every process's chips — IS the locked cloud.
XLA collectives over ICI/DCN replace the reference's UDP+TCP RPC.

Single-controller semantics are preserved: after :func:`init_distributed`
the process-global mesh (``parallel/mesh.py``) covers ALL processes' devices,
frames upload row-sharded across hosts (each process materializes its own
row range — ``jax.make_array_from_callback``), and every jitted step is the
same SPMD program on every process.
"""

from __future__ import annotations

import jax
import numpy as np

_initialized = False
#: the coordinator args the live runtime was initialized with — a SECOND
#: init_distributed with different args used to silently no-op (the caller
#: believed it had joined cloud B while still wired to cloud A); now it
#: raises (see init_distributed)
_init_args: tuple | None = None


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     local_device_ids=None) -> None:
    """Join (or form) a multi-process cloud and install the spanning mesh.

    Mirrors ``h2o.init(...)`` on a multi-node cluster: blocks until all
    ``num_processes`` processes have connected to the coordinator (the
    reference's ``waitForCloudSize``), then installs a global 1-D ``"rows"``
    mesh over every device in the cloud.

    On a single process (all args None) this is a no-op beyond mesh setup.
    Re-initializing with the SAME coordinator args is idempotent (the cloud
    is already formed); different args raise — JAX's distributed runtime
    cannot re-home a live process onto another coordinator, and silently
    keeping the old cloud is the worst possible answer.
    """
    global _initialized, _init_args
    if coordinator_address is not None:
        args = (coordinator_address, num_processes, process_id,
                tuple(local_device_ids)
                if local_device_ids is not None else None)
        if _initialized:
            if args != _init_args:
                raise RuntimeError(
                    "init_distributed called twice with different "
                    f"coordinator args: already joined {_init_args!r}, "
                    f"requested {args!r}. A process cannot leave one cloud "
                    "for another; call shutdown_distributed() first (and "
                    "note live arrays from the old cloud do not survive).")
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids)
            _initialized = True
            _init_args = args
    # (re)install the default mesh over the now-global device set
    from h2o3_tpu.parallel.mesh import set_mesh
    set_mesh(None)


def shutdown_distributed() -> None:
    """Leave the cloud. Idempotent: a second call (or a call on a process
    that never initialized) is a no-op."""
    global _initialized, _init_args
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
        _init_args = None


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def fetch(arr: jax.Array) -> np.ndarray:
    """Gather a (possibly cross-process row-sharded) array to every host.

    Single-process: plain ``device_get``. Multi-process: non-addressable
    shards are exchanged via an all-gather collective (the reference's
    equivalent is a ``TaskGetKey`` fetch of remote chunks to the caller)."""
    if not isinstance(arr, jax.Array) or arr.is_fully_addressable:
        return np.asarray(jax.device_get(arr))
    return _allgather(arr)


def _allgather(arr) -> np.ndarray:
    """Cross-host gather of non-addressable shards, under the dispatch
    retry budget: this is the one cross-host dispatch outside the
    ``map_reduce`` sites, and a transient DCN hiccup here used to be the
    only unretried failure path in the stack (docs/RELIABILITY.md).

    Collective caveat: a retry re-enters the allgather rendezvous on THIS
    process only, so absorption is sound for failures every participant
    observes (XLA collectives fail collectively — a timed-out rendezvous
    raises on all hosts, and all retry together) and for pre-dispatch
    faults local to this host (the injected-chaos case). A failure mode
    where one host errors while its peers return would desynchronize
    regardless of retry policy; that class is fail-fast by nature and
    surfaces as the eventual rendezvous timeout."""
    from jax.experimental import multihost_utils

    from h2o3_tpu.ops.map_reduce import retrying

    def _attempt():
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    return retrying("allgather", _attempt)


def barrier(name: str = "sync") -> None:
    """Cross-process sync point (reference: ``MRTask`` blocking ``doAll``)."""
    if is_multiprocess():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
