"""Multi-process (multi-host) runtime — the TPU-native "cloud".

Reference: a multi-node H2O cloud forms by heartbeat gossip until every JVM
agrees on the member list (``water/H2O.java:1890`` ``startLocalNode``,
``:2099`` ``waitForCloudSize``; ``water/Paxos.java``). The TPU equivalent is
JAX's multi-controller runtime: every process runs the same program, calls
:func:`jax.distributed.initialize` against a coordinator address, and the
global device mesh — spanning every process's chips — IS the locked cloud.
XLA collectives over ICI/DCN replace the reference's UDP+TCP RPC.

Single-controller semantics are preserved: after :func:`init_distributed`
the process-global mesh (``parallel/mesh.py``) covers ALL processes' devices,
frames upload row-sharded across hosts (each process materializes its own
row range — ``jax.make_array_from_callback``), and every jitted step is the
same SPMD program on every process.
"""

from __future__ import annotations

import jax
import numpy as np

_initialized = False


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     local_device_ids=None) -> None:
    """Join (or form) a multi-process cloud and install the spanning mesh.

    Mirrors ``h2o.init(...)`` on a multi-node cluster: blocks until all
    ``num_processes`` processes have connected to the coordinator (the
    reference's ``waitForCloudSize``), then installs a global 1-D ``"rows"``
    mesh over every device in the cloud.

    On a single process (all args None) this is a no-op beyond mesh setup.
    """
    global _initialized
    if coordinator_address is not None and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids)
        _initialized = True
    # (re)install the default mesh over the now-global device set
    from h2o3_tpu.parallel.mesh import set_mesh
    set_mesh(None)


def shutdown_distributed() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def fetch(arr: jax.Array) -> np.ndarray:
    """Gather a (possibly cross-process row-sharded) array to every host.

    Single-process: plain ``device_get``. Multi-process: non-addressable
    shards are exchanged via an all-gather collective (the reference's
    equivalent is a ``TaskGetKey`` fetch of remote chunks to the caller)."""
    if not isinstance(arr, jax.Array) or arr.is_fully_addressable:
        return np.asarray(jax.device_get(arr))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def barrier(name: str = "sync") -> None:
    """Cross-process sync point (reference: ``MRTask`` blocking ``doAll``)."""
    if is_multiprocess():
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
